"""Training-step builder: the GRACE `DistributedOptimizer` hook, TPU-style.

Reference flow (SURVEY.md §3.1): backward -> per-gradient compensate ->
compress -> allgather -> decompress -> aggregate -> memory.update ->
optimizer.step, orchestrated by GRACE inside Horovod's optimizer wrapper.
Here the whole step is ONE spmd function under `shard_map` over the data
axis of a `jax.sharding.Mesh`:

- params / optimizer state are replicated (every worker applies the same
  aggregated update, like the reference's synchronous DP);
- the residual error-feedback state is *worker-local* — it lives sharded
  over the mesh's data axis with a leading [num_workers] dim outside the
  shard_map (the reference keeps it in per-process GRACE memory);
- batch is sharded over the data axis;
- the gradient exchange is `deepreduce_tpu.comm.GradientExchanger`.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import ConfigError, DeepReduceConfig
from deepreduce_tpu.metrics import WireStats
from deepreduce_tpu.resilience import faults
from deepreduce_tpu.telemetry import MetricAccumulators, spans


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TrainState:
    params: Any
    batch_stats: Any  # flax BatchNorm running stats ({} if unused)
    opt_state: Any
    residuals: Any  # worker-local error-feedback (None if memory='none')
    step: jax.Array


def classification_loss(model) -> Callable:
    """(params, batch_stats, batch) -> (loss, new_batch_stats) for flax
    models with optional BatchNorm; batch = (images, int labels)."""

    def loss_fn(params, batch_stats, batch):
        images, labels = batch
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
            logits, mutated = model.apply(
                variables, images, train=True, mutable=["batch_stats"]
            )
            new_stats = mutated["batch_stats"]
        else:
            logits = model.apply(variables, images)
            new_stats = batch_stats
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
        return loss, new_stats

    return loss_fn


def make_worker_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    exchanger: GradientExchanger,
    *,
    telemetry: bool = False,
) -> Callable:
    """The per-worker spmd step (call inside shard_map over the exchanger's
    axis). With `telemetry=True` the step takes and returns a
    `MetricAccumulators` pytree as an extra carry — all telemetry
    quantities are collective-reduced on device, so the accumulator stays
    replicated and the hot loop never syncs to host."""
    axis = exchanger.axis_name
    cfg = exchanger.cfg
    # Python-level gate (like `if telemetry:` below): the resilience-off
    # step is built from the identical source path with no mask arithmetic,
    # so its jaxpr is byte-identical to a pre-resilience build (pinned by
    # tests/test_resilience.py + the jx-resilience-off-identical rule)
    resilient = bool(cfg.resilience)
    if resilient and (cfg.drop_rate > 0.0 or cfg.fault_plan is not None):
        if exchanger.num_workers is None:
            raise ValueError(
                "participation masks need the static mesh size: construct "
                "GradientExchanger(..., num_workers=mesh.shape[axis])"
            )
    # Python-level gate like `resilient`: the streaming-off step traces the
    # identical source path as before, so its jaxpr stays byte-identical.
    # config.__post_init__ guarantees stream_exchange never combines with
    # resilience, so the mask branch below is dead under streaming. The
    # scheduling leg composes over flat AND hierarchical stacks
    # (exchange.wrap_streaming — the stream-over-hier path runs each
    # bucket's ici psum inside its backward hook).
    from deepreduce_tpu.exchange import wrap_streaming

    streaming = wrap_streaming(exchanger)

    def step_fn(state: TrainState, batch, key: jax.Array, acc=None):
        collect = {} if telemetry else None
        if streaming is not None:
            # the whole exchange happens INSIDE this span: each bucket's
            # encode+gather dispatches from the custom_vjp backward rules,
            # so the exchange/bucket/* spans land within forward_backward
            with spans.span("train/forward_backward"):
                (loss, new_stats), grads, agg, new_residuals, wire = (
                    streaming.value_and_grad_exchange(
                        loss_fn,
                        state.params,
                        state.batch_stats,
                        batch,
                        state.residuals,
                        step=state.step,
                        key=key,
                        collect=collect,
                    )
                )
        else:
            with spans.span("train/forward_backward"):
                (loss, new_stats), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params, state.batch_stats, batch)
        loss = jax.lax.pmean(loss, axis)
        if new_stats:
            new_stats = jax.lax.pmean(new_stats, axis)

        mask = None
        if resilient:
            with spans.span("resilience/mask"):
                # derived from the SHARED step key (pre worker fold_in), so
                # every worker computes the identical replicated mask
                mask = faults.participation_mask(
                    exchanger.num_workers,
                    state.step,
                    key,
                    drop_rate=cfg.drop_rate,
                    fault_plan=cfg.fault_plan,
                )
        if streaming is None:
            with spans.span("train/exchange"):
                agg, new_residuals, wire = exchanger.exchange(
                    grads,
                    state.residuals,
                    step=state.step,
                    key=key,
                    collect=collect,
                    mask=mask,
                )
        with spans.span("train/apply_updates"):
            updates, new_opt = optimizer.update(agg, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
        wire_mean = WireStats(
            index_bits=jax.lax.pmean(wire.index_bits.astype(jnp.float32), axis),
            value_bits=jax.lax.pmean(wire.value_bits.astype(jnp.float32), axis),
            dense_bits=wire.dense_bits.astype(jnp.float32),
            # saturation is a COUNT (summed, not averaged): total saturated
            # tensor payloads across all workers this step
            saturated=jax.lax.psum(wire.saturated.astype(jnp.float32), axis),
            # ICI-fabric bits are a static per-device count (identical on
            # every device), so no collective: a concrete 0.0 in flat
            # exchanges, which keeps this line out of pre-hier jaxprs
            ici_bits=jnp.asarray(wire.ici_bits, jnp.float32)
            if isinstance(wire.ici_bits, jax.core.Tracer)
            else np.float32(wire.ici_bits),
        )
        new_state = TrainState(
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt,
            residuals=new_residuals,
            step=state.step + 1,
        )
        if not telemetry:
            return new_state, loss, wire_mean

        # --- telemetry accumulator update (all collective-reduced) ------ #
        from jax.flatten_util import ravel_pytree

        # compression error vs. the dense mean gradient: what a lossless
        # allreduce would have applied, one extra pmean per step
        dense_mean = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g.astype(jnp.float32), axis), grads
        )
        af, _ = ravel_pytree(
            jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), agg)
        )
        df, _ = ravel_pytree(dense_mean)
        ref = jnp.linalg.norm(df)
        err_l2 = jnp.linalg.norm(af - df) / jnp.maximum(ref, 1e-12)
        err_cos = jnp.vdot(af, df) / jnp.maximum(jnp.linalg.norm(af) * ref, 1e-12)
        if new_residuals is not None:
            res_sq = sum(
                jnp.sum(jnp.square(r.astype(jnp.float32)))
                for r in jax.tree_util.tree_leaves(new_residuals)
            )
            residual_l2 = jax.lax.pmean(jnp.sqrt(res_sq), axis)
        else:
            residual_l2 = jnp.zeros((), jnp.float32)
        # per-bucket saturation counts, f32[C] (only present when the
        # bucketed exchange ran); summed over workers like `saturated`
        bucket_sat = collect.get("bucket_saturated")
        # resilience counters: live worker count, whether any worker sat
        # this step out, and checksum failures over gathered rows (the
        # failure count is replicated — every worker decodes the same
        # gathered buffer — so no psum)
        total_w = jnp.asarray(jax.lax.psum(1, axis), jnp.float32)
        if mask is not None:
            live = jnp.sum(mask.astype(jnp.float32))
            dropped = (live < total_w).astype(jnp.float32)
        else:
            live = total_w
            dropped = jnp.zeros((), jnp.float32)
        new_acc = acc.accumulate(
            wire_mean,
            residual_l2=residual_l2,
            err_l2=err_l2,
            err_cos=err_cos,
            fp_count=jax.lax.psum(collect["fp_count"], axis),
            fp_universe=jax.lax.psum(collect["fp_universe"], axis),
            live_workers=live,
            dropped_steps=dropped,
            checksum_failures=collect.get("checksum_failures", 0.0),
            # adaptive sparse_rs: per-worker shard density and dense-switch
            # flag, pmean'd so the accumulator stores the mean shard
            # density and the fraction of phase-2 rows sent dense
            rs_density=jax.lax.pmean(collect["rs_density"], axis)
            if "rs_density" in collect
            else 0.0,
            rs_dense_switches=jax.lax.pmean(collect["rs_dense_switches"], axis)
            if "rs_dense_switches" in collect
            else 0.0,
            # oktopk sparse_rs: survivor count and threshold are psum'd
            # inside the route (identical on every worker — pmean is the
            # identity aggregate); spills are per-worker, pmean'd to the
            # mean spilled survivors per worker
            rs_oktopk_survivors=jax.lax.pmean(collect["rs_oktopk_survivors"], axis)
            if "rs_oktopk_survivors" in collect
            else 0.0,
            rs_oktopk_threshold=jax.lax.pmean(collect["rs_oktopk_threshold"], axis)
            if "rs_oktopk_threshold" in collect
            else 0.0,
            rs_oktopk_spills=jax.lax.pmean(collect["rs_oktopk_spills"], axis)
            if "rs_oktopk_spills" in collect
            else 0.0,
            bucket_saturated=(
                jax.lax.psum(bucket_sat, axis) if bucket_sat is not None else 0.0
            ),
        )
        return new_state, loss, wire_mean, new_acc

    return step_fn


class Trainer:
    """End-to-end distributed trainer over a mesh data axis — the role of the
    reference's benchmark driver + GRACE wiring (run_deepreduce.sh)."""

    def __init__(
        self,
        model,
        cfg: DeepReduceConfig,
        optimizer: optax.GradientTransformation,
        mesh: Optional[Mesh] = None,
        *,
        axis_name: str = "data",
        loss_fn: Optional[Callable] = None,
    ):
        self.model = model
        self.cfg = cfg
        self.optimizer = optimizer
        if cfg.fed:
            # loud fence, not a silent ignore: the federated round (sync or
            # async) is driven by fedsim.FedSim / fedavg.FedAvg, never by
            # the data-parallel Trainer — a fed config here would train
            # with the fed_* (and fed_async*) knobs silently dropped
            raise ConfigError(
                "fed-vs-trainer",
                "fed=True configures the federated simulation "
                "(deepreduce_tpu.fedsim); the Trainer runs the "
                "data-parallel gradient exchange and would silently ignore "
                "every fed_* knob — build a FedSim (or drop fed=True)"
            )
        if cfg.hier:
            # hierarchical mode runs over a two-axis (dcn, ici) mesh. Build
            # it from cfg.ici_size when none is passed (the one mesh factory
            # owns the DCN-aware layout), or validate a caller-supplied mesh
            # actually has both axes — a flat mesh here would silently
            # collapse the hierarchy.
            from deepreduce_tpu.parallel.hierarchical import make_hybrid_mesh

            if mesh is None:
                if cfg.ici_size is None:
                    raise ValueError(
                        "hier=True with no mesh needs cfg.ici_size to split "
                        "the devices into (dcn, ici); set ici_size or pass a "
                        "two-axis mesh"
                    )
                n_dev = len(jax.devices())
                if n_dev % cfg.ici_size:
                    raise ValueError(
                        f"ici_size={cfg.ici_size} does not divide the "
                        f"device count {n_dev}"
                    )
                mesh = make_hybrid_mesh(n_dev // cfg.ici_size, cfg.ici_size)
            else:
                missing = {"dcn", "ici"} - set(mesh.axis_names)
                if missing:
                    raise ValueError(
                        f"hier=True needs a (dcn, ici) mesh; the given mesh "
                        f"lacks axis(es) {sorted(missing)}"
                    )
                if cfg.ici_size is not None and mesh.shape["ici"] != cfg.ici_size:
                    raise ValueError(
                        f"cfg.ici_size={cfg.ici_size} contradicts the given "
                        f"mesh's ici extent {mesh.shape['ici']}"
                    )
            self.axis_name = ("dcn", "ici")
        else:
            if mesh is None:
                raise ValueError("a mesh is required when cfg.hier is False")
            self.axis_name = axis_name
        self.mesh = mesh
        self.loss_fn = loss_fn or classification_loss(model)
        self.exchanger: Optional[GradientExchanger] = None
        self._step_fn = None
        self._raw_step_fn = None  # unjitted shard_map'd fn (audit hook)
        self._telemetry_acc: Optional[MetricAccumulators] = None
        # last fetched cumulative counters, baseline for the window_* rows
        self._prev_summary_fetch = None
        # --- adaptive controller (cfg.ctrl) ---------------------------- #
        # One exchanger + one jitted step PER LADDER RUNG, built lazily and
        # cached by rung index: the controller only ever swaps which cached
        # program runs, so the compiled-executable count is bounded by
        # len(ladder) (pinned by tests/test_controller.py and the
        # jx-ctrl-ladder audit). All of it is Python-level and absent when
        # ctrl=False — the off step program stays byte-identical.
        self._ctrl = None
        self._step_cache = {}
        self._raw_step_cache = {}
        self._exchanger_cache = {}
        self._params_like = None
        # --- profile-driven re-selection (apply_profile) ---------------- #
        # Mirrors the controller's bounded-retrace contract with plan
        # tuples as keys: one exchanger + one jitted step per distinct
        # auto-selected plan, so applying a fitted machine profile costs at
        # most one extra compile — and zero when the profile agrees with
        # the static constants (cache size == plans visited, pinned by the
        # jx-calib-reselect audit and tests/test_calibrate.py).
        self._plan_key = None
        self._plan_step_cache = {}
        self._plan_raw_cache = {}
        self._plan_ex_cache = {}
        # host-side mirror of state.step: synced from the device ONCE at
        # the first step() (resume-safe), then incremented locally — so the
        # telemetry-boundary check never adds a per-step host sync
        self._host_step = None
        if cfg.ctrl:
            from deepreduce_tpu.controller import CompressionController

            self._ctrl = CompressionController(cfg)

    @property
    def num_workers(self) -> int:
        if isinstance(self.axis_name, tuple):
            n = 1
            for a in self.axis_name:
                n *= self.mesh.shape[a]
            return n
        return self.mesh.shape[self.axis_name]

    def init_state(self, rng: jax.Array, sample_batch) -> TrainState:
        sample_input = sample_batch[0]
        if isinstance(sample_input, (tuple, list)):
            variables = self.model.init(rng, *sample_input)
        else:
            variables = self.model.init(rng, sample_input)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        self._params_like = params
        if self._ctrl is not None:
            # start at the rung nearest cfg.compress_ratio; residual and
            # opt-state shapes are rung-invariant (dense gradient shapes),
            # so the state built here carries across every rung switch
            self.exchanger = self._exchanger_for(self._ctrl.index)
        else:
            # the composed-leg factory: hier configs get the two-tier
            # wrapper on the (dcn, ici) mesh, flat configs the one-axis
            # exchanger (exchange.leg_plan describes the result)
            from deepreduce_tpu.exchange import build_exchanger

            self.exchanger = build_exchanger(
                params, self.cfg,
                axis_name=self.axis_name,
                num_workers=self.num_workers,
                num_slices=self.mesh.shape["dcn"] if self.cfg.hier else None,
                per_slice=self.mesh.shape["ici"] if self.cfg.hier else None,
            )
        residuals = self.exchanger.init_state(params)
        if residuals is not None:
            # worker-local residual: leading [num_workers] axis, sharded
            residuals = jax.tree_util.tree_map(
                lambda r: jnp.broadcast_to(r[None], (self.num_workers,) + r.shape), residuals
            )
        if self._ctrl is None:
            self._plan_key = self._plan_key_of(self.exchanger)
            if self._plan_key is not None:
                self._plan_ex_cache[self._plan_key] = self.exchanger
        state = TrainState(
            params=params,
            batch_stats=batch_stats,
            opt_state=self.optimizer.init(params),
            residuals=residuals,
            step=jnp.asarray(0, jnp.int32),
        )
        if self._ctrl is not None:
            # commit the fresh state to the exact shardings the jitted step
            # emits (replicated carries, worker-sharded residuals): an
            # uncommitted first-step input would specialize one extra
            # throwaway executable, breaking the one-executable-per-rung
            # accounting the controller audits and tests pin
            from jax.sharding import NamedSharding, PartitionSpec

            state = jax.device_put(
                dataclasses.replace(state, residuals=None),
                NamedSharding(self.mesh, PartitionSpec()),
            )
            if residuals is not None:
                residuals = jax.device_put(
                    residuals, NamedSharding(self.mesh, PartitionSpec(self.axis_name))
                )
            state = dataclasses.replace(state, residuals=residuals)
        return state

    def _exchanger_for(self, idx: int) -> GradientExchanger:
        """The (cached) flat exchanger for ladder rung `idx`: the base
        config with the rung's ratio/fpr substituted, plus the per-bucket
        operating-point vector once the bucket count is known (uniform
        under the default all-buckets-together policy)."""
        ex = self._exchanger_cache.get(idx)
        if ex is None:
            cfg_i = self._ctrl.ladder.apply(self.cfg, idx)
            # first build discovers the bucket partition; later rungs thread
            # the explicit per-bucket point vector through comm_bucket
            points = None
            if self.exchanger is not None and self.exchanger.num_buckets:
                pt = self._ctrl.ladder[idx]
                points = tuple(
                    (pt.ratio, pt.fpr) for _ in range(self.exchanger.num_buckets)
                )
            ex = GradientExchanger(
                self._params_like, cfg_i, axis_name=self.axis_name,
                num_workers=self.num_workers, bucket_points=points,
            )
            self._exchanger_cache[idx] = ex
        return ex

    def _control_update(self):
        """One controller evaluation at a telemetry fetch boundary: fetch
        the cumulative counters (the sync that telemetry_every already
        pays), let the controller vote on the window delta, and on a
        switch swap in the cached exchanger/step for the new rung."""
        with spans.span("ctrl/update"):
            fetch = self._telemetry_acc.fetch()
            decision = self._ctrl.observe(self._host_step, fetch)
        if decision is None or not decision["switched"]:
            return
        idx = self._ctrl.index
        self.exchanger = self._exchanger_for(idx)
        self._step_fn = self._step_cache.get(idx)
        self._raw_step_fn = self._raw_step_cache.get(idx)

    def _build(self, has_residuals: bool):
        telemetry = bool(self.cfg.telemetry)
        worker_step = make_worker_step(
            self.loss_fn, self.optimizer, self.exchanger, telemetry=telemetry
        )
        axis = self.axis_name

        # the telemetry accumulator is an extra replicated carry that only
        # exists when cfg.telemetry is on — the off program is built from
        # the identical source path with no extra args, so its jaxpr is
        # byte-identical to a build without telemetry (pinned by
        # tests/test_telemetry.py via the analysis retrace hash)
        if telemetry:

            def spmd(state_nores, residuals, batch, key, acc):
                if residuals is not None:
                    residuals = jax.tree_util.tree_map(lambda r: r[0], residuals)
                state = dataclasses.replace(state_nores, residuals=residuals)
                new_state, loss, wire, new_acc = worker_step(state, batch, key, acc)
                new_res = new_state.residuals
                if new_res is not None:
                    new_res = jax.tree_util.tree_map(lambda r: r[None], new_res)
                return (
                    dataclasses.replace(new_state, residuals=None),
                    new_res,
                    loss,
                    wire,
                    new_acc,
                )

            extra_in, extra_out = (P(),), (P(),)
        else:

            def spmd(state_nores, residuals, batch, key):
                if residuals is not None:
                    residuals = jax.tree_util.tree_map(lambda r: r[0], residuals)
                state = dataclasses.replace(state_nores, residuals=residuals)
                new_state, loss, wire = worker_step(state, batch, key)
                new_res = new_state.residuals
                if new_res is not None:
                    new_res = jax.tree_util.tree_map(lambda r: r[None], new_res)
                return dataclasses.replace(new_state, residuals=None), new_res, loss, wire

            extra_in, extra_out = (), ()

        res_spec = P(axis) if has_residuals else P()
        from deepreduce_tpu.utils.compat import shard_map

        fn = shard_map(
            spmd,
            mesh=self.mesh,
            in_specs=(P(), res_spec, P(axis), P()) + extra_in,
            out_specs=(P(), res_spec, P(), P()) + extra_out,
            check_vma=False,
        )
        self._raw_step_fn = fn  # unjitted, for make_jaxpr-based audits
        # donate the step carries (replicated state, worker-local residuals,
        # and the telemetry accumulator) so XLA updates them in place instead
        # of doubling peak HBM across params + opt_state; batch and key are
        # consumed fresh each step and stay undonated. Donation is a
        # jit-level buffer annotation — the traced program (and therefore
        # the telemetry retrace-hash contract on _raw_step_fn) is unchanged.
        donate = (0, 1, 4) if telemetry else (0, 1)
        return jax.jit(fn, donate_argnums=donate)

    def step(self, state: TrainState, batch, key: jax.Array):
        """One synchronous DP step. batch's leading dim is the global batch,
        split over the data axis."""
        if self._ctrl is not None:
            if self._host_step is None:
                self._host_step = int(state.step)
            if (
                self._host_step > 0
                and self._host_step % self.cfg.telemetry_every == 0
                and self._telemetry_acc is not None
            ):
                self._control_update()
        if self._step_fn is None:
            with spans.span("train/build"):
                self._step_fn = self._build(state.residuals is not None)
            if self._ctrl is not None:
                self._step_cache[self._ctrl.index] = self._step_fn
                self._raw_step_cache[self._ctrl.index] = self._raw_step_fn
            elif self._plan_key is not None:
                self._plan_step_cache[self._plan_key] = self._step_fn
                self._plan_raw_cache[self._plan_key] = self._raw_step_fn
        state_nores = dataclasses.replace(state, residuals=None)
        if self.cfg.telemetry:
            if self._telemetry_acc is None:
                # commit the fresh zeros to the replicated sharding the
                # jitted step emits — an uncommitted accumulator would make
                # jit specialize twice (one executable for the first step,
                # another for the rest), breaking the one-executable-per-
                # ladder-rung accounting the controller tests pin
                from jax.sharding import NamedSharding, PartitionSpec

                self._telemetry_acc = jax.device_put(
                    MetricAccumulators.zeros(
                        num_buckets=self.exchanger.num_buckets
                    ),
                    NamedSharding(self.mesh, PartitionSpec()),
                )
            new_nores, new_res, loss, wire, self._telemetry_acc = self._step_fn(
                state_nores, state.residuals, batch, key, self._telemetry_acc
            )
        else:
            new_nores, new_res, loss, wire = self._step_fn(
                state_nores, state.residuals, batch, key
            )
        if self._ctrl is not None:
            self._host_step += 1
        return dataclasses.replace(new_nores, residuals=new_res), loss, wire

    @property
    def telemetry(self) -> Optional[MetricAccumulators]:
        """The live on-device accumulator (None until the first telemetry
        step, or when cfg.telemetry is off)."""
        return self._telemetry_acc

    def telemetry_summary(self) -> dict:
        """Fetch the accumulators to host (the telemetry_every sync point);
        {} when telemetry is off or no step has run. Alongside the
        cumulative rows, `window_*` keys carry the same rates over the
        span since the previous call (the controller's view)."""
        if self._telemetry_acc is None:
            return {}
        from deepreduce_tpu.telemetry.device_metrics import fetch_delta

        acc = self._telemetry_acc
        vals = acc.fetch()
        out = acc.derive(vals)
        # first call: no baseline yet, so the window IS the cumulative run
        window_src = (
            vals
            if self._prev_summary_fetch is None
            else fetch_delta(vals, self._prev_summary_fetch)
        )
        out.update({f"window_{k}": v for k, v in acc.derive(window_src).items()})
        self._prev_summary_fetch = vals
        return out

    # ------------------------------------------------------------------ #
    # fitted-profile re-selection surface (costmodel.MachineProfile)
    # ------------------------------------------------------------------ #

    @staticmethod
    def _plan_key_of(exchanger) -> Optional[Tuple]:
        """The auto-selected plan identity of an exchanger, or None when
        every selector was explicit (nothing a profile could re-select)."""
        plan = getattr(exchanger, "plan", None)
        if plan is not None:
            return ("hier", plan["ici"], plan["dcn"])
        if exchanger.cfg.rs_mode == "auto":
            # hier without auto legs delegates the rs resolution to its
            # inner cross-slice GradientExchanger
            inner = getattr(exchanger, "exchanger", exchanger)
            return ("rs", inner._rs_mode)
        return None

    @property
    def visited_plan_keys(self) -> Tuple[Tuple, ...]:
        """Auto-selected plans a step program was actually compiled for —
        the bounded-retrace witness for profile-driven re-selection
        (== distinct compiled step executables on this path)."""
        return tuple(sorted(self._plan_step_cache))

    def apply_profile(self, profile) -> dict:
        """Re-run this config's 'auto' plan selection under a fitted
        machine profile (a costmodel.MachineProfile or a path to one) and,
        when the calibrated argmin differs from the current plan, swap in
        the re-selected exchanger and its (cached or lazily rebuilt)
        step program. Contract: a profile that agrees with the static
        constants is a no-op (same plan key, same program — pinned by the
        jx-calib-reselect audit), and the compiled-executable count stays
        == len(visited_plan_keys). Returns the decision record."""
        from deepreduce_tpu import costmodel

        if self._ctrl is not None:
            raise ValueError(
                "apply_profile with ctrl=True would fight the adaptive "
                "controller for the operating point — use one or the other"
            )
        if self.exchanger is None or self._params_like is None:
            raise ValueError("apply_profile requires init_state() first")
        if isinstance(profile, (str, bytes)) or hasattr(profile, "__fspath__"):
            profile = costmodel.load_profile(profile)
        old_key = self._plan_key_of(self.exchanger)
        if old_key is None:
            return {
                "switched": False,
                "old": None,
                "new": None,
                "reason": "no 'auto' selector in the config — nothing to "
                          "re-select",
            }
        new_key = None
        for key, ex in self._plan_ex_cache.items():
            if getattr(ex, "profile", None) is profile:
                new_key, new_ex = key, ex
                break
        if new_key is None:
            if self.cfg.hier:
                from deepreduce_tpu.parallel.hierarchical import (
                    HierarchicalExchanger,
                )

                new_ex = HierarchicalExchanger(
                    self._params_like, self.cfg,
                    num_slices=self.mesh.shape["dcn"],
                    per_slice=self.mesh.shape["ici"],
                    profile=profile,
                )
            else:
                new_ex = GradientExchanger(
                    self._params_like, self.cfg, axis_name=self.axis_name,
                    num_workers=self.num_workers, profile=profile,
                )
            new_key = self._plan_key_of(new_ex)
        record = {
            "switched": new_key != old_key,
            "old": old_key,
            "new": new_key,
            "fitted": tuple(profile.fitted),
        }
        if getattr(new_ex, "plan", None) is not None:
            plan = new_ex.plan
            record["modeled_new_s"] = plan["modeled_step_s"]
            record["modeled_old_s"] = plan["table"][f"{old_key[1]}+{old_key[2]}"]
        if new_key == old_key:
            # same plan: keep the committed exchanger and compiled program —
            # the candidate differs only in the profile it consulted
            return record
        self.exchanger = new_ex
        self._plan_ex_cache[new_key] = new_ex
        self._plan_key = new_key
        # swap in the cached program for the re-selected plan; a miss means
        # the next step() lazily builds (and caches) exactly one more
        self._step_fn = self._plan_step_cache.get(new_key)
        self._raw_step_fn = self._plan_raw_cache.get(new_key)
        return record

    # ------------------------------------------------------------------ #
    # adaptive controller surface (cfg.ctrl)
    # ------------------------------------------------------------------ #

    @property
    def controller(self):
        """The live CompressionController (None when cfg.ctrl is off)."""
        return self._ctrl

    @property
    def visited_ladder_indices(self) -> Tuple[int, ...]:
        """Ladder rungs a step program was actually compiled for — the
        bounded-re-jit witness (== distinct compiled step executables)."""
        return tuple(sorted(self._step_cache))

    def attach_decision_log(self, path) -> None:
        """Persist every controller decision to `path` (decisions.jsonl)."""
        if self._ctrl is None:
            raise ValueError("attach_decision_log requires cfg.ctrl=True")
        from deepreduce_tpu.controller import DecisionLog

        self._ctrl.log = DecisionLog(path)

    def controller_state(self) -> dict:
        """Controller state pytree for checkpoint stamping (call after
        init_state so the bucket geometry is known)."""
        if self._ctrl is None:
            raise ValueError("controller_state requires cfg.ctrl=True")
        if self.exchanger is None:
            raise ValueError("controller_state requires init_state() first")
        return self._ctrl.state_dict(self.exchanger.num_buckets)

    def load_controller_state(self, state: dict) -> None:
        """Restore a checkpointed controller trajectory: the next decision
        continues bitwise from the restored window baseline and vote
        streaks (enforced by `make ctrl-check`)."""
        if self._ctrl is None:
            raise ValueError("load_controller_state requires cfg.ctrl=True")
        self._ctrl.load_state_dict(state)
        idx = self._ctrl.index
        self.exchanger = self._exchanger_for(idx)
        self._step_fn = self._step_cache.get(idx)
        self._raw_step_fn = self._raw_step_cache.get(idx)
        self._host_step = None  # re-sync from state.step at the next step()
